"""CRD types: TpuOperatorConfig and ServiceFunctionChain.

Reference: api/v1/dpuoperatorconfig_types.go:29-36 (cluster-scoped singleton,
``spec.mode`` ∈ host/dpu/auto, ``spec.logLevel``) and
api/v1/servicefunctionchain_types.go:27-34 (namespaced, shortName sfc, a list
of {name, image} network functions).

The TPU build keeps both shapes and adds the TPU-specific spec surface the
north star requires: the accelerator side is a TPU VM ("tpu" mode ≈ reference
"dpu" mode: the daemon runs next to the chips), and the config may pin an
expected slice topology (e.g. "v5e-16") that detection validates against.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import vars as v

GROUP = "config.tpu.openshift.io"
VERSION = "v1"
API_VERSION = f"{GROUP}/{VERSION}"

#: reference mode values host/dpu/auto (dpuoperatorconfig_webhook.go:50-61);
#: here "tpu" replaces "dpu" — the side running on the accelerator VM.
MODES = ("host", "tpu", "auto")


#: default NF secondary-interface range when spec.nfIpam is unset; NF pods
#: need per-interface addressing for chain traffic (VERDICT r1 item 2;
#: reference: networkfn.go:233-317 delegates to the NetConf's IPAM)
DEFAULT_NF_IPAM = {"type": "host-local", "subnet": "10.56.0.0/24"}

#: upgradeStrategy.type values: blueGreen stages the new VSP next to the
#: old one and promotes only once the health engine reports it Healthy;
#: recreate replaces in place (dev clusters — brief dataplane gap).
UPGRADE_TYPES = ("blueGreen", "recreate")


@dataclass
class UpgradeStrategy:
    """spec.upgradeStrategy: controller-driven VSP replacement.

    ``vsp_image`` names the TARGET VSP image; whenever it differs from
    ``status.upgrade.currentImage`` the controller runs the staged
    rollout (controller/vsp_rollout.py): stage the new VSP, gate on the
    health engine (/debug/health fold — a burn-rate alert or degraded
    breaker holds the rollout with an ``UpgradeHeld`` Event), then
    drain the old one. Empty ``vsp_image`` = no controller-driven VSP
    management (the daemons deploy their own, the pre-upgrade
    behavior)."""
    type: str = "blueGreen"
    vsp_image: str = ""
    #: gate promotion on the health engine snapshot (disable only in
    #: dev clusters with no health engine running)
    health_gate: bool = True
    #: how long the controller waits between gate checks while the new
    #: VSP stages (ReconcileResult.requeue_after)
    check_interval: float = 5.0

    def to_dict(self) -> dict:
        return {"type": self.type, "vspImage": self.vsp_image,
                "healthGate": self.health_gate,
                "checkIntervalSeconds": self.check_interval}

    @classmethod
    def from_dict(cls, d: dict) -> "UpgradeStrategy":
        return cls(
            type=d.get("type", "blueGreen"),
            vsp_image=d.get("vspImage", ""),
            health_gate=bool(d.get("healthGate", True)),
            check_interval=float(d.get("checkIntervalSeconds", 5.0)),
        )


@dataclass
class TpuOperatorConfigSpec:
    mode: str = "auto"
    log_level: int = 0
    #: optional expected slice topology, e.g. "v5e-4", "v5p-32"; empty = accept
    #: whatever detection finds.
    slice_topology: str = ""
    #: IPAM config embedded into the network-function NAD (host-local or
    #: static); defaults to DEFAULT_NF_IPAM.
    nf_ipam: dict = field(default_factory=lambda: dict(DEFAULT_NF_IPAM))
    #: controller-driven blue-green VSP replacement; None = unmanaged.
    upgrade_strategy: "UpgradeStrategy | None" = None

    def to_dict(self) -> dict:
        out = {
            "mode": self.mode,
            "logLevel": self.log_level,
            "sliceTopology": self.slice_topology,
            "nfIpam": dict(self.nf_ipam),
        }
        if self.upgrade_strategy is not None:
            out["upgradeStrategy"] = self.upgrade_strategy.to_dict()
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "TpuOperatorConfigSpec":
        strategy = d.get("upgradeStrategy")
        return cls(
            mode=d.get("mode", "auto"),
            log_level=d.get("logLevel", 0),
            slice_topology=d.get("sliceTopology", ""),
            nf_ipam=dict(d.get("nfIpam") or DEFAULT_NF_IPAM),
            upgrade_strategy=(UpgradeStrategy.from_dict(strategy)
                              if strategy else None),
        )


@dataclass
class TpuOperatorConfig:
    name: str = v.CONFIG_NAME
    spec: TpuOperatorConfigSpec = field(default_factory=TpuOperatorConfigSpec)
    uid: str = ""

    KIND = "TpuOperatorConfig"

    def to_obj(self) -> dict:
        md = {"name": self.name}
        if self.uid:
            md["uid"] = self.uid
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": md,
            "spec": self.spec.to_dict(),
        }

    @classmethod
    def from_obj(cls, obj: dict) -> "TpuOperatorConfig":
        return cls(
            name=obj.get("metadata", {}).get("name", ""),
            spec=TpuOperatorConfigSpec.from_dict(obj.get("spec", {})),
            uid=obj.get("metadata", {}).get("uid", ""),
        )


#: version of the TpuNodeTelemetry status digest schema; aggregators
#: ignore digests from a future schema (and count them) instead of
#: misreading fields that moved
TELEMETRY_SCHEMA_VERSION = 1


@dataclass
class TpuNodeTelemetry:
    """Namespaced per-node telemetry digest CR (the fleet telemetry
    plane's publish side). One object per node daemon, named after the
    node; the daemon publishes its judged local state — health
    components, serve headroom, fault-engine quarantines, active SLO
    alerts, watchdog stalls — into ``status`` on a damped cadence
    (daemon/telemetry.py), and the operator's FleetAggregator consumes
    every object through one shared informer
    (controller/fleet_telemetry.py). The spec is intentionally tiny:
    the object IS its status."""

    name: str
    namespace: str = v.NAMESPACE
    uid: str = ""

    KIND = "TpuNodeTelemetry"

    def to_obj(self) -> dict:
        md: dict = {"name": self.name, "namespace": self.namespace}
        if self.uid:
            md["uid"] = self.uid
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": md,
            "spec": {"node": self.name},
        }

    @classmethod
    def from_obj(cls, obj: dict) -> "TpuNodeTelemetry":
        md = obj.get("metadata", {})
        return cls(name=md.get("name", ""),
                   namespace=md.get("namespace", v.NAMESPACE),
                   uid=md.get("uid", ""))


@dataclass
class NetworkFunction:
    """One element of an SFC (reference: servicefunctionchain_types.go:27-34)."""
    name: str
    image: str

    def to_dict(self) -> dict:
        return {"name": self.name, "image": self.image}


@dataclass
class ServiceFunctionChain:
    name: str
    namespace: str = "default"
    network_functions: list = field(default_factory=list)
    uid: str = ""
    #: boundary binding (external-traffic analog of the reference's
    #: pod↔NF↔external e2e, e2e_test.go:348-513): slice-attachment names
    #: traffic enters the chain from / leaves it into — typically the
    #: host-side attachments of tenant workload pods. Empty = the chain
    #: floats (NF-to-NF steering only).
    ingress: str = ""
    egress: str = ""

    KIND = "ServiceFunctionChain"

    def to_obj(self) -> dict:
        md = {"name": self.name, "namespace": self.namespace}
        if self.uid:
            md["uid"] = self.uid
        spec = {
            "networkFunctions": [
                nf.to_dict() if isinstance(nf, NetworkFunction) else nf
                for nf in self.network_functions
            ],
        }
        if self.ingress:
            spec["ingress"] = self.ingress
        if self.egress:
            spec["egress"] = self.egress
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": md,
            "spec": spec,
        }

    @classmethod
    def from_obj(cls, obj: dict) -> "ServiceFunctionChain":
        nfs = [
            NetworkFunction(name=nf.get("name", ""), image=nf.get("image", ""))
            for nf in obj.get("spec", {}).get("networkFunctions", [])
        ]
        return cls(
            name=obj.get("metadata", {}).get("name", ""),
            namespace=obj.get("metadata", {}).get("namespace", "default"),
            network_functions=nfs,
            uid=obj.get("metadata", {}).get("uid", ""),
            ingress=obj.get("spec", {}).get("ingress", ""),
            egress=obj.get("spec", {}).get("egress", ""),
        )
