#!/usr/bin/env python
"""Rewrite Dockerfile FROM lines for layer-cached incremental builds
(reference: tools/incremental/incremental.go:11-40 — point FROM at the
previously built local image so unchanged layers are reused)."""

import argparse
import re
import sys

_FROM_RE = re.compile(r"^(FROM\s+)(\S+)(\s+AS\s+\S+)?\s*$", re.I)


def rewrite(text: str, registry: str, tag: str) -> str:
    out = []
    for line in text.splitlines():
        m = _FROM_RE.match(line)
        if m and "/" not in m.group(2) and not m.group(2).startswith(
                ("python", "gcc", "debian", "ubuntu", "scratch")):
            image = f"{registry}/{m.group(2)}:{tag}"
            line = f"{m.group(1)}{image}{m.group(3) or ''}"
        out.append(line)
    return "\n".join(out) + "\n"


def main(argv=None):
    parser = argparse.ArgumentParser("incremental")
    parser.add_argument("dockerfile")
    parser.add_argument("--registry", required=True)
    parser.add_argument("--tag", default="latest")
    args = parser.parse_args(argv)
    with open(args.dockerfile) as f:
        sys.stdout.write(rewrite(f.read(), args.registry, args.tag))


if __name__ == "__main__":
    main()
