#!/usr/bin/env python3
"""Bench trajectory: parse the checked-in BENCH_r*.json rounds into a
per-metric trend table with direction-aware regression flags.

Each PR's bench run leaves a ``BENCH_rNN.json`` behind (``n``, ``cmd``,
``rc``, ``tail``, ``parsed``). Individually they answer "how fast is it
now"; this tool lines them up so `make bench-trend` answers "which
metrics drifted, and which way". The parsed payload is flattened
(nested dicts join with '.'), every numeric leaf becomes a series over
rounds, and the LAST round is judged against the median of the earlier
rounds it appeared in:

- a metric whose name says which way is good (tokens_per_s up,
  ttft_p99_s down) gets a verdict — ``regressed`` / ``improved`` when
  the relative delta clears the noise band, ``steady`` inside it;
- a metric with no recognizable direction is reported neutrally
  (``changed``/``steady``) and never fails ``--strict``.

The band defaults to 10% because these are single-shot CI-box runs,
not pinned-hardware benchmarks; tune with ``--band``. Output ordering
is fully deterministic (sorted metric names, fixed column widths) so
diffs of the table itself are meaningful.
"""

from __future__ import annotations

import argparse
import json
import re
import statistics
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

# the direction vocabulary lives in the package (the live trend engine
# shares it — utils/metric_direction.py); running this file standalone
# from tools/ needs the repo root on sys.path first
try:
    from dpu_operator_tpu.utils.metric_direction import direction
except ImportError:  # pragma: no cover — standalone invocation
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from dpu_operator_tpu.utils.metric_direction import direction

BENCH_GLOB = "BENCH_r*.json"


def flatten_numeric(value: object, prefix: str = "",
                    out: Optional[Dict[str, float]] = None,
                    ) -> Dict[str, float]:
    """Numeric leaves of a nested dict, '.'-joined paths; bools and
    strings are skipped (device names, flags are not trajectories)."""
    if out is None:
        out = {}
    if isinstance(value, dict):
        for key in sorted(value):
            path = f"{prefix}.{key}" if prefix else str(key)
            flatten_numeric(value[key], path, out)
    elif isinstance(value, bool):
        pass
    elif isinstance(value, (int, float)):
        out[prefix] = float(value)
    return out


def load_rounds(root: Path) -> List[Tuple[int, Dict[str, float]]]:
    """(round, flat-metrics) per bench file, ordered by round number.
    A file that fails to parse or whose run failed (rc != 0) is
    reported on stderr and skipped — a broken round must not poison
    the trend math for the rounds that did run."""
    rounds: List[Tuple[int, Dict[str, float]]] = []
    for path in sorted(root.glob(BENCH_GLOB)):
        match = re.search(r"BENCH_r(\d+)\.json$", path.name)
        if not match:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, ValueError) as e:
            print(f"bench-trend: skipping {path.name}: {e}",
                  file=sys.stderr)
            continue
        if doc.get("rc", 0) != 0:
            print(f"bench-trend: skipping {path.name}: bench rc="
                  f"{doc.get('rc')}", file=sys.stderr)
            continue
        n = int(doc.get("n") or match.group(1))
        rounds.append((n, flatten_numeric(doc.get("parsed") or {})))
    rounds.sort(key=lambda r: r[0])
    return rounds


def build_series(rounds: List[Tuple[int, Dict[str, float]]],
                 ) -> Dict[str, List[Tuple[int, float]]]:
    series: Dict[str, List[Tuple[int, float]]] = {}
    for n, flat in rounds:
        for metric, value in flat.items():
            series.setdefault(metric, []).append((n, value))
    return series


def judge(values: List[float], sign: int, band: float) -> Tuple[str, float]:
    """(verdict, relative delta of last vs median-of-prior)."""
    if len(values) < 2:
        return "single", 0.0
    ref = statistics.median(values[:-1])
    last = values[-1]
    if ref == 0.0:
        delta = 0.0 if last == 0.0 else float("inf")
    else:
        delta = (last - ref) / abs(ref)
    if abs(delta) < band:
        return "steady", delta
    if sign == 0:
        return "changed", delta
    good = delta * sign > 0
    return ("improved" if good else "regressed"), delta


def render(series: Dict[str, List[Tuple[int, float]]], band: float,
           ) -> Tuple[List[str], List[str]]:
    """(table lines, regressed metric names), both sorted."""
    lines = [f"{'metric':<56} {'dir':>4} {'rounds':>6} "
             f"{'first':>12} {'last':>12} {'delta':>8}  verdict"]
    regressed: List[str] = []
    for metric in sorted(series):
        points = series[metric]
        values = [v for _, v in points]
        sign = direction(metric)
        verdict, delta = judge(values, sign, band)
        if verdict == "regressed":
            regressed.append(metric)
        arrow = {1: "up", -1: "down", 0: "?"}[sign]
        delta_s = ("-" if verdict == "single"
                   else f"{delta:+.1%}" if abs(delta) != float("inf")
                   else "inf")
        lines.append(
            f"{metric:<56} {arrow:>4} {len(points):>6} "
            f"{values[0]:>12.6g} {values[-1]:>12.6g} {delta_s:>8}  "
            f"{verdict}")
    return lines, regressed


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="bench_trend",
        description="per-metric trajectory over the checked-in "
                    "BENCH_r*.json rounds, with noise-banded "
                    "regression flags")
    parser.add_argument("--dir", default=str(Path(__file__)
                                             .resolve().parent.parent),
                        help="directory holding BENCH_r*.json "
                             "(default: repo root)")
    parser.add_argument("--band", type=float, default=0.10,
                        help="relative noise band (default 0.10)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 when any direction-known metric "
                             "regressed beyond the band")
    args = parser.parse_args(argv)
    rounds = load_rounds(Path(args.dir))
    if not rounds:
        print("bench-trend: no BENCH_r*.json rounds found",
              file=sys.stderr)
        return 2
    series = build_series(rounds)
    lines, regressed = render(series, args.band)
    print(f"bench-trend: {len(rounds)} rounds "
          f"(r{rounds[0][0]:02d}..r{rounds[-1][0]:02d}), "
          f"{len(series)} metrics, band {args.band:.0%}")
    for line in lines:
        print(line)
    if regressed:
        print(f"\nregressed ({len(regressed)}):")
        for metric in regressed:
            print(f"  {metric}")
    if args.strict and regressed:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
